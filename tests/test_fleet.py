"""Fleet-serving tests (tier-1): AOT executable export/import and the
zero-compile warm start; router affinity/health-gating/failover/
hedging; supervised restart of crashed and wedged replicas; rolling
weight updates behind the verify + canary gates; the end-to-end chaos
drill (``scripts/serve_fleet_smoke.py --tiny``).

Budget discipline: ONE engine compiles the single ``(40, 56) x b2``
program and exports it (module-scoped ``aot_dir``); every fleet in the
file imports that artifact, so fleets construct in well under a second
and no test but the fixture pays a JIT compile."""

import importlib.util
import json
import os.path as osp
import threading
import time

import numpy as np
import pytest

from raft_tpu import chaos
from raft_tpu.config import RAFTConfig
from raft_tpu.serve import (FleetConfig, FlowRouter, InferenceEngine,
                            ReplicaFleet, RouterConfig, ServeConfig,
                            WeightUpdateError)
from raft_tpu.serve import aot as aot_mod
from raft_tpu.serve.router import is_failover_error

REPO = osp.dirname(osp.dirname(osp.abspath(__file__)))

CFG = RAFTConfig.small_model()  # fp32: CPU-friendly, matches test_serve
ITERS = 2
SHAPE = (36, 52)                # -> bucket (40, 56)


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, osp.join(REPO, "scripts", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _serve_cfg(**kw):
    base = dict(iters=ITERS, max_batch=2, batch_sizes=(2,),
                max_wait_ms=5, max_queue=64)
    base.update(kw)
    return ServeConfig(**base)


def _images(rng, h=SHAPE[0], w=SHAPE[1]):
    return (rng.uniform(0, 255, (h, w, 3)).astype(np.float32),
            rng.uniform(0, 255, (h, w, 3)).astype(np.float32))


def _wait_for(pred, timeout_s, what):
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        if pred():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {what}")


@pytest.fixture(autouse=True)
def _no_leaked_plan():
    chaos.uninstall()
    yield
    chaos.uninstall()


@pytest.fixture(scope="module")
def variables():
    import jax

    from raft_tpu.models.raft import RAFT

    model_img = jax.numpy.zeros((1, 40, 56, 3))
    rng = jax.random.PRNGKey(0)
    return RAFT(CFG).init({"params": rng, "dropout": rng},
                          model_img, model_img, iters=1)


@pytest.fixture(scope="module")
def aot_dir(variables, tmp_path_factory):
    """The file's ONE compile: warm a throwaway engine and export."""
    d = str(tmp_path_factory.mktemp("aot"))
    eng = InferenceEngine(variables, CFG, _serve_cfg())
    eng.start()
    try:
        eng.warmup([SHAPE])
        eng.export_aot(d)
    finally:
        eng.stop()
    return d


def _mk_fleet(variables, aot_dir, *, replicas=2, scfg=None, **fcfg_kw):
    kw = dict(replicas=replicas, aot_dir=aot_dir,
              warmup_shapes=(SHAPE,), auto_export_aot=False,
              restart_backoff_s=0.05, restart_backoff_max_s=0.4,
              health_poll_s=0.05)
    kw.update(fcfg_kw)
    return ReplicaFleet(variables, CFG, scfg or _serve_cfg(),
                        FleetConfig(**kw))


# ---------------------------------------------------------------------------
# AOT export/import
# ---------------------------------------------------------------------------


def test_model_fingerprint_sensitivity(variables):
    """The fingerprint must move with anything that changes the traced
    program: iters, leaf shapes/dtypes, and the tree STRUCTURE (an
    empty added collection changes the input pytree without changing a
    single leaf — the smoke drill's original failure mode)."""
    fp = aot_mod.model_fingerprint(CFG, variables, ITERS)
    assert fp == aot_mod.model_fingerprint(CFG, variables, ITERS)
    assert fp != aot_mod.model_fingerprint(CFG, variables, ITERS + 1)
    restructured = dict(variables, batch_stats={})
    assert fp != aot_mod.model_fingerprint(CFG, restructured, ITERS)


def test_aot_import_gates_and_corruption(variables, aot_dir, tmp_path):
    """A good artifact round-trips; a wrong fingerprint, a truncated
    blob, and a missing directory are each refused with
    ``AOTImportError`` (all-or-nothing: no partial import)."""
    import shutil

    fp = aot_mod.model_fingerprint(CFG, variables, ITERS)
    exes = aot_mod.import_executables(aot_dir, fingerprint=fp)
    assert set(exes) == {((40, 56), 2, "enc"), ((40, 56), 2, "iter")}

    with pytest.raises(aot_mod.AOTImportError, match="fingerprint"):
        aot_mod.import_executables(aot_dir, fingerprint="deadbeef")
    with pytest.raises(aot_mod.AOTImportError, match="manifest"):
        aot_mod.import_executables(str(tmp_path / "nope"),
                                   fingerprint=fp)

    torn = tmp_path / "torn"
    shutil.copytree(aot_dir, torn)
    blob = next(p for p in torn.iterdir()
                if p.name.startswith("exe-"))
    blob.write_bytes(blob.read_bytes()[:100])
    with pytest.raises(aot_mod.AOTImportError, match="checksum"):
        aot_mod.import_executables(str(torn), fingerprint=fp)


def test_engine_aot_preload_zero_compiles(variables, aot_dir):
    """An engine built with ``aot_dir`` serves its first request with
    CompileCounter == 0 — the fleet's warm-start contract."""
    eng = InferenceEngine(variables, CFG,
                          _serve_cfg(aot_dir=aot_dir))
    assert eng.aot_info["ok"] is True and eng.aot_info["imported"] == 2
    eng.start()
    try:
        im1, im2 = _images(np.random.default_rng(1))
        flow = eng.infer(im1, im2, timeout=120)
        assert flow.shape == SHAPE + (2,)
        assert np.isfinite(flow).all()
        assert eng.compile_counter.counts() == {}
        assert eng.stats()["aot"]["imported"] == 2
    finally:
        eng.stop()


def test_engine_aot_miss_falls_back_to_lazy_jit(variables, tmp_path):
    """An unusable artifact dir is a warm-start MISS, not a serve
    failure: the engine logs it and compiles lazily."""
    eng = InferenceEngine(variables, CFG,
                          _serve_cfg(aot_dir=str(tmp_path / "empty")))
    assert eng.aot_info["ok"] is False
    eng.start()
    try:
        im1, im2 = _images(np.random.default_rng(1))
        assert eng.infer(im1, im2, timeout=120).shape == SHAPE + (2,)
        assert eng.compile_counter.counts() == {
            ((40, 56), 2, "enc"): 1, ((40, 56), 2, "iter"): 1}
    finally:
        eng.stop()


# ---------------------------------------------------------------------------
# router
# ---------------------------------------------------------------------------


def test_failover_error_classification():
    from raft_tpu.chaos import InjectedReplicaKill, ReplicaWedgedInterrupt
    from raft_tpu.serve import QueueFullError

    assert is_failover_error(InjectedReplicaKill("kill"))
    assert is_failover_error(ReplicaWedgedInterrupt("wedge"))
    assert is_failover_error(RuntimeError("engine stopped — ..."))
    assert is_failover_error(RuntimeError("engine crashed: reason"))
    assert not is_failover_error(ValueError("bad shapes"))
    assert not is_failover_error(QueueFullError("full"))


def test_router_affinity_fallback_and_breaker(variables, aot_dir):
    """Placement policy: the bucket's affine replica gets the traffic;
    exclusion or an open breaker reroutes to the sibling; the breaker
    closes again after its cooldown."""
    fleet = _mk_fleet(variables, aot_dir)
    fleet.start()
    try:
        router = FlowRouter(fleet, RouterConfig(breaker_threshold=1,
                                                breaker_cooldown_s=0.3))
        bucket = (40, 56)
        affine = router._pick(bucket, set())
        other = next(r for r in fleet.replicas if r is not affine)
        assert router._pick(bucket, set()) is affine  # deterministic
        assert router._pick(bucket, {affine.name}) is other
        affine.note_failure(1, 0.3)          # breaker opens
        assert affine.breaker_open()
        assert router._pick(bucket, set()) is other
        time.sleep(0.35)                     # cooldown passes
        assert router._pick(bucket, set()) is affine
        assert router._pick(bucket, {affine.name, other.name}) is None

        # live traffic actually lands on the affine replica
        rng = np.random.default_rng(2)
        for _ in range(3):
            router.infer(*_images(rng), timeout=120)
        by_rep = router.router_stats()["requests_by_replica"]
        assert by_rep == {affine.name: 3}
    finally:
        fleet.stop()


def test_kill_failover_no_dropped_requests(variables, aot_dir):
    """The acceptance drill in unit form: a chaos ``replica_kill``
    mid-load fails the victim's in-flight batch over to the sibling;
    every accepted future resolves, the dropped tripwire stays 0, and
    the supervisor restarts the victim with ZERO compiles (AOT)."""
    fleet = _mk_fleet(variables, aot_dir)
    fleet.start()
    try:
        router = FlowRouter(fleet, RouterConfig())
        chaos.install(chaos.FaultPlan.parse("replica_kill@batch=2",
                                            seed=0))
        rng = np.random.default_rng(3)
        futs = []
        for _ in range(8):
            futs.append(router.submit(*_images(rng)))
            time.sleep(0.01)
        results = [f.result(timeout=120) for f in futs]
        assert all(r.shape == SHAPE + (2,) for r in results)
        rstats = router.router_stats()
        assert rstats["dropped_total"] == 0
        assert rstats["failovers_total"] >= 1
        _wait_for(lambda: sum(r.restarts for r in fleet.replicas) == 1
                  and all(r.state == "ready" for r in fleet.replicas),
                  30, "supervised restart")
        victim = next(r for r in fleet.replicas if r.restarts)
        assert victim.engine.aot_info["ok"] is True
        assert victim.engine.compile_counter.counts() == {}
        assert router.infer(*_images(rng),
                            timeout=120).shape == SHAPE + (2,)
        assert victim.engine.compile_counter.counts() == {}
        assert 'reason="crash"' in fleet.metrics_text()
    finally:
        fleet.stop()


def test_hang_detected_as_stall_and_restarted(variables, aot_dir):
    """A wedged device worker (``replica_hang``) never raises on its
    own — the stall watchdog turns health not-ready, the supervisor
    restarts the replica, the interrupted batch fails over, and the
    requests still resolve."""
    scfg = _serve_cfg(stall_timeout_s=0.3, chaos_hang_max_s=20.0)
    fleet = _mk_fleet(variables, aot_dir, scfg=scfg)
    fleet.start()
    try:
        router = FlowRouter(fleet, RouterConfig())
        chaos.install(chaos.FaultPlan.parse("replica_hang@batch=1",
                                            seed=0))
        rng = np.random.default_rng(4)
        futs = [router.submit(*_images(rng)) for _ in range(2)]
        results = [f.result(timeout=60) for f in futs]
        assert all(r.shape == SHAPE + (2,) for r in results)
        _wait_for(lambda: sum(r.restarts for r in fleet.replicas) == 1
                  and all(r.state == "ready" for r in fleet.replicas),
                  30, "stall-triggered restart")
        assert 'reason="stall"' in fleet.metrics_text()
    finally:
        fleet.stop()


def test_hedge_covers_straggler(variables, aot_dir):
    """``replica_slow`` makes the primary's batch a straggler; the
    router's bounded hedge duplicates the request onto the sibling,
    which answers first (hedge win) long before the straggler."""
    scfg = _serve_cfg(chaos_slow_s=3.0)
    fleet = _mk_fleet(variables, aot_dir, scfg=scfg)
    fleet.start()
    try:
        router = FlowRouter(fleet,
                            RouterConfig(hedge_timeout_s=0.25))
        chaos.install(chaos.FaultPlan.parse("replica_slow@batch=1",
                                            seed=0))
        rng = np.random.default_rng(5)
        t0 = time.perf_counter()
        flow = router.infer(*_images(rng), timeout=60)
        dt = time.perf_counter() - t0
        assert flow.shape == SHAPE + (2,)
        assert dt < 2.5, f"hedge did not cover the {dt:.1f}s straggler"
        rstats = router.router_stats()
        assert rstats["hedges_total"] == 1
        assert rstats["hedge_wins_total"] == 1
        assert rstats["dropped_total"] == 0
    finally:
        fleet.stop(drain=False)


# ---------------------------------------------------------------------------
# rolling weight updates + fleet lifecycle
# ---------------------------------------------------------------------------


def test_rolling_update_flips_and_gates(variables, aot_dir):
    """An in-memory weight update flips every replica (zero compiles —
    the AOT artifact is weight-independent) and changes what the fleet
    serves; NaN weights and a missing checkpoint dir are refused with
    the version unchanged."""
    import jax

    from raft_tpu.models.raft import RAFT

    fleet = _mk_fleet(variables, aot_dir)
    fleet.start()
    try:
        router = FlowRouter(fleet, RouterConfig())
        rng = np.random.default_rng(6)
        im1, im2 = _images(rng)
        before = router.infer(im1, im2, timeout=120)

        k = jax.random.PRNGKey(9)
        model_img = jax.numpy.zeros((1, 40, 56, 3))
        new_vars = jax.device_get(RAFT(CFG).init(
            {"params": k, "dropout": k}, model_img, model_img, iters=1))
        report = fleet.update_weights(new_vars)
        assert report["ok"] and sorted(report["flipped"]) == ["r0", "r1"]
        assert fleet.weights_version == 2
        for r in fleet.replicas:  # flip kept the zero-compile start
            assert r.engine.compile_counter.counts() == {}
            assert r.generation >= 2
        after = router.infer(im1, im2, timeout=120)
        assert after.shape == before.shape
        assert not np.allclose(after, before), \
            "new weights served identical flow — flip did not take"

        poisoned = jax.tree_util.tree_map(
            lambda x: np.full_like(x, np.nan), new_vars)
        with pytest.raises(WeightUpdateError, match="canary"):
            fleet.update_weights(poisoned)
        assert fleet.weights_version == 2
        with pytest.raises(WeightUpdateError, match="not found"):
            fleet.update_weights("/nonexistent/ckpt-dir")
        assert fleet.weights_version == 2
        assert fleet.health()["ready"]
    finally:
        fleet.stop()


def test_scrambled_weights_refused_by_proxy_canary(variables, aot_dir):
    """Finite-but-garbage weights (every param scaled x25) sail through
    the shape+finiteness canary — the flow is the right shape and all
    finite, just wild — and are refused at the golden-batch quality
    proxy gate instead (``FleetConfig.canary_proxy_budget``).  The
    version stays put and the fleet keeps serving the old weights."""
    import jax

    scrambled = jax.tree_util.tree_map(
        lambda x: np.asarray(x) * 25.0, jax.device_get(variables))
    fleet = _mk_fleet(variables, aot_dir)
    fleet.start()
    try:
        router = FlowRouter(fleet, RouterConfig())
        rng = np.random.default_rng(11)
        im1, im2 = _images(rng)
        before = router.infer(im1, im2, timeout=120)
        version0 = fleet.weights_version
        with pytest.raises(WeightUpdateError, match="proxy"):
            fleet.update_weights(scrambled)
        assert fleet.weights_version == version0
        after = router.infer(im1, im2, timeout=120)
        assert np.allclose(after, before), \
            "refused update changed what the fleet serves"
        assert fleet.health()["ready"]
    finally:
        fleet.stop()


def test_fleet_stop_during_update_warmup_joins_cleanly(variables,
                                                      aot_dir):
    """``fleet.stop(drain=True)`` racing a rolling update's warmup must
    join cleanly: the warming engine is stopped, the update fails with
    ``WeightUpdateError`` instead of hanging, and no replica flips."""
    fleet = _mk_fleet(variables, aot_dir)
    fleet.start()
    gate = threading.Event()
    entered = threading.Event()
    real_canary = fleet._canary

    def blocking_canary(warming):
        entered.set()
        gate.wait(timeout=30)
        return real_canary(warming)

    fleet._canary = blocking_canary
    outcome = {}

    def update():
        try:
            outcome["report"] = fleet.update_weights(
                {k: v for k, v in variables.items()})
        except BaseException as e:  # noqa: BLE001 — recorded for asserts
            outcome["error"] = e

    t = threading.Thread(target=update)
    t.start()
    assert entered.wait(timeout=30), "update never reached the canary"
    warming = fleet._warming
    assert warming is not None
    t0 = time.perf_counter()
    fleet.stop(drain=True)
    assert time.perf_counter() - t0 < 30
    gate.set()
    t.join(timeout=30)
    assert not t.is_alive(), "update thread hung after fleet.stop()"
    assert isinstance(outcome.get("error"), WeightUpdateError), outcome
    assert warming._stopped
    assert fleet.weights_version == 1
    assert all(r.state == "stopped" for r in fleet.replicas)


# ---------------------------------------------------------------------------
# the end-to-end drill
# ---------------------------------------------------------------------------


def test_serve_fleet_smoke_tiny(capsys):
    """The chaos drill the PR promises: AOT warm start, replica kill
    under open-loop load with zero dropped accepted requests, restart
    with zero compiles, verify+canary-gated rolling update."""
    mod = _load_script("serve_fleet_smoke")
    rc = mod.main(["--tiny", "--requests", "10"])
    out = capsys.readouterr().out.strip().splitlines()
    rec = json.loads(out[-1])
    assert rc == 0
    assert rec["metric"] == "serve_fleet_smoke" and rec["value"] == 1.0
    drill = rec["config"]["kill_drill"]
    assert drill["dropped"] == 0 and drill["failovers"] >= 1
    assert sum(drill["restarts"].values()) >= 1
    assert rec["config"]["rolling_update"]["version"] == 2
