"""fuse_upsample_in_scan (single-scan training path) numerics parity vs
the default two-scan path: same losses, metrics, gradients, and — by
construction via function-form nn.scan scope binding — the same param
tree / checkpoints."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu.config import RAFTConfig
from raft_tpu.models.raft import RAFT

pytestmark = pytest.mark.slow


def test_fused_inscan_matches_two_scan():
    rng = np.random.default_rng(0)
    B, H, W = 2, 48, 64
    img1 = jnp.asarray(rng.uniform(0, 255, (B, H, W, 3)), jnp.float32)
    img2 = jnp.asarray(rng.uniform(0, 255, (B, H, W, 3)), jnp.float32)
    gt = jnp.asarray(rng.standard_normal((B, H, W, 2)), jnp.float32)
    valid = jnp.ones((B, H, W), jnp.float32)

    cfg2 = RAFTConfig.full()                       # two-scan
    cfg1 = cfg2.replace(fuse_upsample_in_scan=True)
    m2, m1 = RAFT(cfg2), RAFT(cfg1)
    k = jax.random.PRNGKey(0)
    # One init serves both: the fused path must bind the identical
    # refine/upsampler scopes (checkpoint compatibility).
    v = m2.init({"params": k, "dropout": k}, img1, img2, iters=2,
                train=False)
    kwargs = dict(iters=4, train=True, freeze_bn=True,
                  loss_targets=(gt, valid, 400.0), rngs={"dropout": k},
                  mutable=["batch_stats"])
    (o2, mets2), _ = m2.apply(v, img1, img2, **kwargs)
    (o1, mets1), _ = m1.apply(v, img1, img2, **kwargs)
    np.testing.assert_allclose(np.asarray(o1), np.asarray(o2),
                               rtol=1e-5, atol=1e-7)
    for kk in mets2:  # scalars AND the per-iteration epe_iter curve
        np.testing.assert_allclose(np.asarray(mets1[kk]),
                                   np.asarray(mets2[kk]), rtol=1e-5,
                                   err_msg=kk)

    def loss_fn(model):
        def f(params):
            vv = {"params": params, "batch_stats": v["batch_stats"]}
            (per, _), _ = model.apply(vv, img1, img2, **kwargs)
            g = 0.8 ** jnp.arange(3, -1, -1)
            return jnp.sum(per * g)
        return f

    g2 = jax.grad(loss_fn(m2))(v["params"])
    g1 = jax.grad(loss_fn(m1))(v["params"])
    for (p, a), (_, b) in zip(jax.tree_util.tree_leaves_with_path(g1),
                              jax.tree_util.tree_leaves_with_path(g2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=1e-7,
                                   err_msg=jax.tree_util.keystr(p))
