"""Streaming-session serving tests (tier-1).

The contracts pinned here are the streaming acceptance criteria
(docs/SERVING.md "Streaming sessions"):

- **Warm start wins**: over a >= 8-frame clip with known analytic
  motion (``scripts/make_demo_frames.make_clip``) every frame after
  the first pair takes the warm path, the warm ``iters_used`` p50
  sits strictly below the cold p50, and the compile ledger shows
  exactly one ``enc`` + ``iter`` + ``stash`` + ``wenc`` program per
  ``(bucket, slots)``.
- **Cold parity**: a session's FIRST pair is bit-identical to the
  stateless slot path — the cold pair runs the unmodified ``enc``
  executable; the carry stash is a separate program.
- **Cheaper warm encoder**: the cost book stamps ``wenc`` with fewer
  FLOPs per pair than ``enc`` (one image encoded instead of two).
- **Sessions are mortal**: the idle TTL evicts a session (freeing its
  pinned lane), and a post to an evicted id transparently re-seeds.
- **Fleet restarts are cold**: a rolling ``update_weights`` and a
  dead replica both cold-restart the session in place (reasons
  ``weights_update`` / ``failover``) — warm state never crosses a
  weights generation or a replica boundary.

Small model, fp32, tiny shapes — compiles stay in the fast tier.
"""

import importlib.util
import os.path as osp
import time

import numpy as np
import pytest

from raft_tpu import chaos
from raft_tpu.config import RAFTConfig
from raft_tpu.serve import (FleetConfig, FlowRouter, InferenceEngine,
                            ReplicaFleet, RouterConfig, ServeConfig)

REPO = osp.dirname(osp.dirname(osp.abspath(__file__)))

CFG = RAFTConfig.small_model()  # fp32 compute: bit-comparable
ITERS = 3
SHAPE = (36, 52)                # -> bucket (40, 56)


@pytest.fixture(autouse=True)
def _no_leaked_plan():
    chaos.uninstall()
    yield
    chaos.uninstall()


class _RecordingSink:
    def __init__(self):
        self.events = []

    def emit(self, event, step=None, **fields):
        self.events.append((event, fields))

    def of(self, event):
        return [f for e, f in self.events if e == event]


def _make_clip(n_frames=8, seed=3):
    spec = importlib.util.spec_from_file_location(
        "make_demo_frames",
        osp.join(REPO, "scripts", "make_demo_frames.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.make_clip(n_frames, SHAPE, shift=(2, 1), seed=seed)


@pytest.fixture(scope="module")
def variables():
    import jax

    from raft_tpu.models.raft import RAFT

    img = jax.numpy.zeros((1, 40, 56, 3))
    rng = jax.random.PRNGKey(0)
    return RAFT(CFG).init({"params": rng, "dropout": rng},
                          img, img, iters=1)


# ---------------------------------------------------------------------------
# forward_warp_flow (the warm-init operator)
# ---------------------------------------------------------------------------


def test_forward_warp_flow_constant_and_zero():
    """A constant integer flow forward-warps to the SAME constant
    everywhere it lands (pure translation transports the field), the
    vacated strip falls back to the cold zero init, and zero flow is
    an exact identity."""
    import jax.numpy as jnp

    from raft_tpu.ops.sampler import forward_warp_flow

    H, W = 10, 12
    flow = jnp.zeros((1, H, W, 2))
    np.testing.assert_array_equal(
        np.asarray(forward_warp_flow(flow)), np.zeros((1, H, W, 2)))

    const = jnp.tile(jnp.asarray([2.0, 1.0]), (1, H, W, 1))
    warped = np.asarray(forward_warp_flow(const))[0]
    # Landed region: rows >= 1, cols >= 2 received the splat.
    np.testing.assert_allclose(
        warped[1:, 2:],
        np.broadcast_to([2.0, 1.0], warped[1:, 2:].shape), atol=1e-5)
    # Vacated strip: nothing splatted there -> zeros (cold init).
    np.testing.assert_array_equal(warped[0, :], 0.0)
    np.testing.assert_array_equal(warped[:, :2][1:], 0.0)


def test_forward_warp_flow_out_of_frame_drops():
    """Flow pointing entirely out of frame leaves an all-zero field
    (every target unhit), not NaNs or garbage."""
    import jax.numpy as jnp

    from raft_tpu.ops.sampler import forward_warp_flow

    flow = jnp.tile(jnp.asarray([1e4, 1e4]), (1, 6, 8, 1))
    out = np.asarray(forward_warp_flow(flow))
    np.testing.assert_array_equal(out, 0.0)


# ---------------------------------------------------------------------------
# engine: streaming e2e + ledger + parity + cost
# ---------------------------------------------------------------------------


def test_stream_e2e_warm_ledger_parity_and_cost(variables):
    """One engine, one 8-frame clip: cold first pair bit-matches the
    stateless path, all later frames are warm with a strictly lower
    iters_used p50, the ledger compiled exactly one program of each
    kind, and the cost book prices wenc under enc."""
    frames, _gt = _make_clip(8)
    sink = _RecordingSink()
    eng = InferenceEngine(variables, CFG, ServeConfig(
        iters=ITERS, batching="slot", slots=4,
        stream_warm_iters=ITERS - 1), sink=sink)
    with eng:
        # Stateless oracle FIRST: same programs, one cold request.
        ref = eng.infer(frames[0], frames[1], timeout=120)

        eng.stream_open("cam0", frames[0])
        outs = []
        for f in frames[1:]:
            outs.append(eng.stream_ingest("cam0", f, timeout=120))
        summary = eng.stream_close("cam0")
        stats = eng.stats()

    # --- cold-first-pair bitwise parity with the stateless path ----
    assert outs[0]["warm"] is False and outs[0]["frame"] == 1
    np.testing.assert_array_equal(outs[0]["flow"], ref)

    # --- every later frame is warm and produced flow ---------------
    assert all(o["warm"] for o in outs[1:])
    assert all(o["flow"].shape == SHAPE + (2,) for o in outs)
    assert all(np.isfinite(o["flow"]).all() for o in outs)
    assert summary["frames"] == 8
    assert summary["pairs"] == 7
    assert summary["warm_pairs"] == 6

    # --- compile ledger: one program each ---------------------------
    counts = eng.compile_counter.counts()
    assert counts == {((40, 56), 4, "enc"): 1,
                      ((40, 56), 4, "iter"): 1,
                      ((40, 56), 4, "stash"): 1,
                      ((40, 56), 4, "wenc"): 1}, counts

    # --- warm p50 strictly below cold p50 ---------------------------
    warm, cold = stats["iters_used_warm"], stats["iters_used_cold"]
    assert warm["count_total"] == 6
    assert cold["count_total"] == 2  # oracle request + session pair 0
    assert warm["p50"] < cold["p50"], (warm, cold)

    # --- warm encoder is cheaper in the compile-time cost model -----
    enc = stats["cost"]["40x56/b4/enc"]
    wenc = stats["cost"]["40x56/b4/wenc"]
    assert wenc["flops_per_pair"] < enc["flops_per_pair"], (enc, wenc)

    # --- events carry the warm split --------------------------------
    retire_warm = [f["warm"] for f in sink.of("serve_retire")]
    assert retire_warm.count(True) == 6
    admits = sink.of("serve_admit")
    assert {a["warm"] for a in admits} == {True, False}
    assert sink.of("stream_open")[0]["sid"] == "cam0"
    assert sink.of("stream_close")[0]["warm_pairs"] == 6

    # --- stats session block (the counter tallies INGESTED frames —
    # the ones that did device work; the seed frame is host-side) ----
    assert stats["sessions"]["frames_total"] == 7


def test_stream_ttl_eviction_and_reseed(variables):
    """An idle session is evicted at its TTL (event + counter + freed
    pin); a later post to the same id transparently re-opens it as a
    fresh frame-0 seed instead of erroring."""
    frames, _gt = _make_clip(3, seed=5)
    sink = _RecordingSink()
    eng = InferenceEngine(variables, CFG, ServeConfig(
        iters=ITERS, batching="slot", slots=2, stream_ttl_s=0.3),
        sink=sink)
    with eng:
        out = eng.stream_ingest("cam0", frames[0], timeout=120)
        assert out["frame"] == 0 and out["flow"] is None
        out = eng.stream_ingest("cam0", frames[1], timeout=120)
        assert out["frame"] == 1 and out["flow"] is not None

        # Expire: the dispatcher's TTL sweep keeps running while the
        # pool is otherwise idle (pinned-lane poll).
        deadline = time.time() + 10
        while time.time() < deadline:
            if eng.stats()["sessions"]["open"] == 0:
                break
            time.sleep(0.05)
        stats = eng.stats()
        assert stats["sessions"]["open"] == 0
        assert stats["sessions"]["evicted_total"] == 1
        ev = sink.of("stream_evict")
        assert len(ev) == 1 and ev[0]["sid"] == "cam0"
        assert ev[0]["idle_s"] >= 0.3 and ev[0]["lane"] >= 0

        # Re-seed: unknown id again -> frame 0, no flow, then warmable.
        out = eng.stream_ingest("cam0", frames[1], timeout=120)
        assert out["frame"] == 0 and out["flow"] is None
        out = eng.stream_ingest("cam0", frames[2], timeout=120)
        assert out["frame"] == 1 and out["flow"] is not None
        eng.stream_close("cam0")


# ---------------------------------------------------------------------------
# fleet: weight updates and failover cold-restart the session
# ---------------------------------------------------------------------------


def test_stream_survives_update_weights_and_failover(variables,
                                                     tmp_path):
    """The two fleet drills on one fleet: (1) a rolling weight update
    cold-restarts the session in place — the next frame re-seeds under
    the new weights (reason ``weights_update``) and the stream then
    resumes warm; (2) the owner replica dying mid-stream fails the
    next frame over to the sibling as a cold restart (reason
    ``failover``) without surfacing an error to the client."""
    import jax

    from raft_tpu.models.raft import RAFT

    frames, _gt = _make_clip(8, seed=7)
    sink = _RecordingSink()
    scfg = ServeConfig(iters=ITERS, batching="slot", slots=2,
                       stream_warm_iters=ITERS - 1)
    # Long health poll: drill (2) needs the router's OWN failover path
    # to see the dead engine before the supervisor does.
    fleet = ReplicaFleet(variables, CFG, scfg, FleetConfig(
        replicas=2, aot_dir=str(tmp_path), auto_export_aot=False,
        warmup_shapes=(), restart_backoff_s=0.05, health_poll_s=5.0))
    fleet.start()
    try:
        router = FlowRouter(fleet, RouterConfig(), sink=sink)

        out = router.stream_ingest("cam0", frames[0], timeout=120)
        assert out["frame"] == 0 and out["flow"] is None
        out = router.stream_ingest("cam0", frames[1], timeout=120)
        assert out["frame"] == 1 and out["warm"] is False
        out = router.stream_ingest("cam0", frames[2], timeout=120)
        assert out["frame"] == 2 and out["warm"] is True

        # ---- drill 1: rolling update -> cold restart ---------------
        k = jax.random.PRNGKey(9)
        img = jax.numpy.zeros((1, 40, 56, 3))
        new_vars = jax.device_get(RAFT(CFG).init(
            {"params": k, "dropout": k}, img, img, iters=1))
        assert fleet.update_weights(new_vars)["ok"]

        out = router.stream_ingest("cam0", frames[3], timeout=120)
        # The restart replayed frame 2 as the new seed, so frame 3
        # still produces a pair — cold, under the NEW weights.
        assert out["frame"] == 3 and out["flow"] is not None
        assert out["warm"] is False
        rst = sink.of("stream_restart")
        assert len(rst) == 1 and rst[0]["reason"] == "weights_update"

        out = router.stream_ingest("cam0", frames[4], timeout=120)
        assert out["frame"] == 4 and out["warm"] is True

        # ---- drill 2: owner dies -> failover cold restart ----------
        # The death must strike DURING the engine call so the router's
        # pre-flight eligibility check passes and the in-call exception
        # path fires (reason "failover") — an engine stopped up front
        # is caught pre-flight as "replica_lost" instead, and a chaos
        # replica_kill races the dispatcher's idle pin-sweep cycles.
        # A one-shot raising wrapper is the deterministic equivalent.
        from raft_tpu.chaos import InjectedReplicaKill

        owner_name = rst[0]["to_replica"]
        owner = next(r for r in fleet.replicas
                     if r.name == owner_name)

        def _die(*a, **kw):
            raise InjectedReplicaKill("test-injected owner death")

        owner.engine.stream_ingest = _die
        out = router.stream_ingest("cam0", frames[5], timeout=120)
        assert out["frame"] == 5 and out["flow"] is not None
        assert out["warm"] is False  # cold restart on the sibling
        rst = sink.of("stream_restart")
        assert len(rst) == 2 and rst[1]["reason"] == "failover"
        assert rst[1]["to_replica"] != owner_name

        out = router.stream_ingest("cam0", frames[6], timeout=120)
        assert out["frame"] == 6 and out["warm"] is True

        summary = router.stream_close("cam0")
        assert summary["restarts"] == 2
        rstats = router.router_stats()
        assert rstats["stream_restarts_total"] == 2
        assert rstats["streams_open"] == 0
    finally:
        fleet.stop(drain=False)
