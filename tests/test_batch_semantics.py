"""Batch->mesh resolution (cli/train.py:resolve_batch): the reference's
2-GPU global batches (train_standard.sh 10/6/6/6) must map onto any pod
slice — round up + linear LR scaling — and --batch_per_chip must pin the
per-device batch exactly."""

import pytest

from raft_tpu.cli.train import resolve_batch


def test_divisible_batch_unchanged():
    assert resolve_batch(10, None, 2, 4e-4) == (10, 4e-4)
    assert resolve_batch(64, None, 64, 1e-4) == (64, 1e-4)


def test_rounds_up_with_linear_lr_scaling():
    b, lr = resolve_batch(10, None, 64, 4e-4)
    assert b == 64
    assert lr == pytest.approx(4e-4 * 6.4)
    b, lr = resolve_batch(6, None, 8, 1.25e-4)
    assert b == 8
    assert lr == pytest.approx(1.25e-4 * 8 / 6)


def test_reference_curriculum_runs_on_1_8_64_devices():
    # Every (stage batch, device count) pair from train_standard.sh on the
    # slices named in VERDICT: resolution must always produce a multiple
    # of the device count.
    for batch in (10, 6):
        for n in (1, 8, 64):
            b, _ = resolve_batch(batch, None, n, 4e-4)
            assert b % n == 0 and b >= batch


def test_batch_per_chip_pins_global():
    assert resolve_batch(6, 4, 8, 1e-4) == (32, 1e-4)


def test_invalid():
    with pytest.raises(ValueError):
        resolve_batch(0, None, 8, 1e-4)
    with pytest.raises(ValueError):
        resolve_batch(8, 0, 8, 1e-4)
