"""The fused/blocked partition heuristic of the on-demand backward
(raft_tpu/ops/pallas_corr.py): pure-shape logic, no kernels — pins WHICH
levels go blocked at the shapes the round-4 hardware runs certified
(BENCH_BEYOND_HBM_r04.json), so a budget/estimate regression cannot
silently put a 56 MB level back into the fused kernel's VMEM.
"""

import jax.numpy as jnp

from raft_tpu.ops.pallas_corr import (_BWD_TILE_H, _FUSED_BWD_BUDGET,
                                      _fused_bwd_est, _odm_levels,
                                      _partition_bwd_levels)


def _pyramid_shapes(H8, W8, C=256, levels=4):
    shapes = []
    h, w = H8, W8
    for _ in range(levels):
        shapes.append((1, h, w, C))
        h, w = h // 2, w // 2
    return shapes


def _nonempty(shapes):
    pyr = [jnp.zeros(s, jnp.float32) for s in shapes]
    ne, _ = _odm_levels(pyr, 9)
    return ne


def _partition(nonempty, block_q=128, k=9):
    blocked, fused = _partition_bwd_levels(nonempty, block_q, k)
    return [lvl for lvl, _ in blocked], [lvl for lvl, _ in fused]


def test_736x1280_stays_fully_fused():
    """The round-3 capability (3.6 pairs/s measured) must keep its
    fused-only backward — moving it to blocked kernels would re-stream
    f2 for no VMEM reason."""
    blocked, fused = _partition(_nonempty(_pyramid_shapes(92, 160)))
    assert blocked == []
    assert fused == [0, 1, 2, 3]


def test_1088x1920_blocks_level0_only():
    blocked, fused = _partition(_nonempty(_pyramid_shapes(136, 240)))
    assert blocked == [0]
    assert fused == [1, 2, 3]


def test_1440x2560_blocks_level0_only():
    blocked, fused = _partition(_nonempty(_pyramid_shapes(180, 320)))
    assert blocked == [0]
    assert fused == [1, 2, 3]


def test_partition_terminates_even_on_absurd_shapes():
    """8K-class: whatever the split, the loop must terminate with every
    level somewhere and the fused remainder under budget."""
    ne = _nonempty(_pyramid_shapes(544, 960))
    blocked, fused = _partition(ne)
    assert sorted(blocked + fused) == [0, 1, 2, 3]
    if fused:
        rem = [x for x in ne if x[0] in fused]
        assert _fused_bwd_est(rem, 128, 9) <= _FUSED_BWD_BUDGET


def test_tile_h_divides_padded_rows():
    assert _BWD_TILE_H >= 1
