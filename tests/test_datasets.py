"""L1 data pipeline tests: synthetic tmpdir fixtures mimic the real corpora
directory layouts (SURVEY.md §4 — the reference has no tests; fixtures stand
in for the 400GB datasets)."""

import os
import os.path as osp

import numpy as np
import pytest
from PIL import Image

from raft_tpu.data import frame_utils
from raft_tpu.data.augment import (ColorJitter, FlowAugmentor,
                                   SparseFlowAugmentor,
                                   resize_sparse_flow_map)
from raft_tpu.data.datasets import (ConcatFlowDataset, FlyingChairs,
                                    FlyingThings3D, HD1K, KITTI,
                                    MpiSintel, ShardedLoader, fetch_dataset)

H, W = 96, 128


def _write_img(path, rng, size=(H, W)):
    arr = rng.integers(0, 255, size=size + (3,), dtype=np.uint8)
    Image.fromarray(arr).save(path)


def _write_ppm(path, rng, size=(H, W)):
    arr = rng.integers(0, 255, size=size + (3,), dtype=np.uint8)
    Image.fromarray(arr).save(path, format="PPM")


@pytest.fixture
def sintel_root(tmp_path):
    rng = np.random.default_rng(0)
    for scene in ["alley_1", "ambush_2"]:
        img_dir = tmp_path / "Sintel/training/clean" / scene
        flow_dir = tmp_path / "Sintel/training/flow" / scene
        img_dir.mkdir(parents=True)
        flow_dir.mkdir(parents=True)
        for i in range(3):
            _write_img(img_dir / f"frame_{i:04d}.png", rng)
        for i in range(2):
            frame_utils.write_flo(
                str(flow_dir / f"frame_{i:04d}.flo"),
                rng.normal(size=(H, W, 2)).astype(np.float32))
    return str(tmp_path / "Sintel")


@pytest.fixture
def chairs_root(tmp_path):
    rng = np.random.default_rng(1)
    data = tmp_path / "FlyingChairs_release/data"
    data.mkdir(parents=True)
    n = 4
    for i in range(n):
        _write_ppm(data / f"{i:05d}_img1.ppm", rng)
        _write_ppm(data / f"{i:05d}_img2.ppm", rng)
        frame_utils.write_flo(str(data / f"{i:05d}_flow.flo"),
                              rng.normal(size=(H, W, 2)).astype(np.float32))
    split = tmp_path / "chairs_split.txt"
    split.write_text("1\n1\n2\n1\n")
    return str(data), str(split)


@pytest.fixture
def kitti_root(tmp_path):
    rng = np.random.default_rng(2)
    img_dir = tmp_path / "KITTI/training/image_2"
    flow_dir = tmp_path / "KITTI/training/flow_occ"
    img_dir.mkdir(parents=True)
    flow_dir.mkdir(parents=True)
    for i in range(2):
        _write_img(img_dir / f"{i:06d}_10.png", rng, size=(H, W))
        _write_img(img_dir / f"{i:06d}_11.png", rng, size=(H, W))
        flow = rng.normal(scale=5, size=(H, W, 2)).astype(np.float32)
        frame_utils.write_flow_kitti(str(flow_dir / f"{i:06d}_10.png"), flow)
    return str(tmp_path / "KITTI")


def test_sintel_pairs_and_load(sintel_root):
    ds = MpiSintel(None, split="training", root=sintel_root, dstype="clean")
    # 2 scenes x (3 frames -> 2 consecutive pairs)
    assert len(ds) == 4 and len(ds.flow_list) == 4
    s = ds.load(0)
    assert s["image1"].shape == (H, W, 3)
    assert s["flow"].shape == (H, W, 2)
    assert s["valid"].shape == (H, W)
    assert s["valid"].all()  # small flows, all |.| < 1000


def test_chairs_split(chairs_root):
    root, split_file = chairs_root
    train = FlyingChairs(None, split="training", root=root,
                         split_file=split_file)
    val = FlyingChairs(None, split="validation", root=root,
                       split_file=split_file)
    assert len(train) == 3 and len(val) == 1


def test_kitti_sparse_load(kitti_root):
    ds = KITTI(None, split="training", root=kitti_root)
    assert len(ds) == 2 and ds.sparse
    s = ds.load(1)
    # KITTI PNG quantizes to 1/64 px
    assert s["flow"].shape == (H, W, 2)
    assert s["valid"].min() >= 0 and s["valid"].max() == 1


def test_mixing_weights_and_concat(sintel_root, kitti_root):
    sintel = MpiSintel(None, split="training", root=sintel_root,
                       dstype="clean")
    kitti = KITTI(None, split="training", root=kitti_root)
    mix = 3 * sintel + 2 * kitti
    assert isinstance(mix, ConcatFlowDataset)
    assert len(mix) == 3 * 4 + 2 * 2
    # The tail of the mixture must route to the sparse member.
    s = mix.load(len(mix) - 1)
    assert s["flow"].shape == (H, W, 2)
    # Replicated indices must resolve to the same underlying sample.
    a = mix.load(0)
    b = mix.load(4)  # second replica of sintel sample 0
    np.testing.assert_array_equal(a["flow"], b["flow"])


def test_fetch_dataset_chairs_stage(chairs_root):
    root, split_file = chairs_root
    ds = fetch_dataset("chairs", (64, 64),
                       root=osp.dirname(osp.dirname(root)),
                       split_file=split_file)
    assert len(ds) == 3
    s = ds.load(0, np.random.default_rng(0))
    assert s["image1"].shape == (64, 64, 3)
    assert s["flow"].shape == (64, 64, 2)


# ---------------------------------------------------------------------------
# Augmentor behavior
# ---------------------------------------------------------------------------

def test_dense_augmentor_shapes_and_determinism():
    rng = np.random.default_rng(7)
    img1 = rng.integers(0, 255, (H, W, 3), dtype=np.uint8)
    img2 = rng.integers(0, 255, (H, W, 3), dtype=np.uint8)
    flow = rng.normal(size=(H, W, 2)).astype(np.float32)
    aug = FlowAugmentor(crop_size=(64, 80))
    for seed in range(4):
        o1 = aug(np.random.default_rng(seed), img1, img2, flow)
        o2 = aug(np.random.default_rng(seed), img1, img2, flow)
        assert o1[0].shape == (64, 80, 3) and o1[2].shape == (64, 80, 2)
        for a, b in zip(o1, o2):
            np.testing.assert_array_equal(a, b)


def test_hflip_flow_sign():
    """A pure-horizontal flow must negate u (not v) under h-flip
    (reference augmentor.py:95)."""
    img = np.full((H, W, 3), 128, np.uint8)
    flow = np.stack([np.full((H, W), 3.0), np.zeros((H, W))],
                    axis=-1).astype(np.float32)
    aug = FlowAugmentor(crop_size=(H - 16, W - 16), do_flip=True,
                        spatial_aug_prob=0.0, eraser_aug_prob=0.0,
                        asymmetric_color_aug_prob=0.0,
                        h_flip_prob=1.0, v_flip_prob=0.0,
                        jitter=ColorJitter(0, 0, 0, 0))
    _, _, out = aug(np.random.default_rng(0), img, img, flow)
    assert np.allclose(out[..., 0], -3.0)
    assert np.allclose(out[..., 1], 0.0)


def test_spatial_scale_scales_flow():
    """Resizing by (sx, sy) must multiply flow components by (sx, sy)
    (reference augmentor.py:89)."""
    img = np.full((H, W, 3), 100, np.uint8)
    flow = np.stack([np.full((H, W), 2.0), np.full((H, W), -1.0)],
                    axis=-1).astype(np.float32)
    aug = FlowAugmentor(crop_size=(64, 64), min_scale=0.5, max_scale=0.5,
                        do_flip=False, spatial_aug_prob=1.0,
                        stretch_prob=0.0, eraser_aug_prob=0.0,
                        asymmetric_color_aug_prob=0.0,
                        jitter=ColorJitter(0, 0, 0, 0))
    _, _, out = aug(np.random.default_rng(0), img, img, flow)
    s = 2.0 ** 0.5
    assert np.allclose(out[..., 0], 2.0 * s, atol=1e-4)
    assert np.allclose(out[..., 1], -1.0 * s, atol=1e-4)


def test_resize_sparse_flow_map_matches_reference():
    """Our vectorized sparse rescale vs the reference's (deterministic, so
    directly comparable; reference augmentor.py:161-193)."""
    from tests.reference_oracle import skip_without_reference
    skip_without_reference()
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "_ref_aug_isolated", "/root/reference/core/utils/augmentor.py")
    try:
        ref_aug = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(ref_aug)
    except ImportError:
        pytest.skip("reference augmentor deps unavailable")

    rng = np.random.default_rng(3)
    flow = rng.normal(scale=10, size=(50, 70, 2)).astype(np.float32)
    valid = (rng.random((50, 70)) < 0.3).astype(np.float32)
    ref = ref_aug.SparseFlowAugmentor.resize_sparse_flow_map(
        None, flow, valid, fx=1.3, fy=0.9)
    ours = resize_sparse_flow_map(flow, valid, fx=1.3, fy=0.9)
    np.testing.assert_allclose(ours[0], ref[0], atol=1e-5)
    np.testing.assert_array_equal(ours[1], ref[1])


def test_sparse_augmentor_shapes():
    rng = np.random.default_rng(11)
    img1 = rng.integers(0, 255, (H, W, 3), dtype=np.uint8)
    img2 = rng.integers(0, 255, (H, W, 3), dtype=np.uint8)
    flow = rng.normal(scale=5, size=(H, W, 2)).astype(np.float32)
    valid = (rng.random((H, W)) < 0.5).astype(np.float32)
    aug = SparseFlowAugmentor(crop_size=(64, 80))
    i1, i2, f, v = aug(np.random.default_rng(0), img1, img2, flow, valid)
    assert i1.shape == (64, 80, 3) and f.shape == (64, 80, 2)
    assert v.shape == (64, 80)
    assert set(np.unique(v)).issubset({0, 1})


# ---------------------------------------------------------------------------
# ShardedLoader
# ---------------------------------------------------------------------------

def test_sharded_loader_batches_and_host_disjointness(sintel_root):
    ds = MpiSintel({"crop_size": (48, 64), "min_scale": -0.1,
                    "max_scale": 0.1, "do_flip": True},
                   split="training", root=sintel_root, dstype="clean")
    loaders = [ShardedLoader(ds, batch_size=1, seed=5, num_hosts=2,
                             host_id=h, num_workers=2) for h in range(2)]
    idx0 = loaders[0].epoch_indices(0)
    idx1 = loaders[1].epoch_indices(0)
    assert not set(idx0) & set(idx1)
    assert sorted(list(idx0) + list(idx1)) == list(range(len(ds)))
    # Shuffle differs across epochs
    assert not np.array_equal(loaders[0].epoch_indices(0),
                              loaders[0].epoch_indices(1))

    it = loaders[0].batches()
    batch = next(it)
    assert batch["image1"].shape == (1, 48, 64, 3)
    assert batch["flow"].shape == (1, 48, 64, 2)
    assert batch["valid"].shape == (1, 48, 64)
    # Infinite stream: crossing the epoch boundary keeps yielding.
    for _ in range(3):
        next(it)


def test_sharded_loader_deterministic(sintel_root):
    ds = MpiSintel({"crop_size": (48, 64), "min_scale": -0.1,
                    "max_scale": 0.1, "do_flip": True},
                   split="training", root=sintel_root, dstype="clean")
    def first_batch():
        return next(ShardedLoader(ds, batch_size=2, seed=9,
                                  num_workers=3).batches())
    b1, b2 = first_batch(), first_batch()
    for k in b1:
        np.testing.assert_array_equal(b1[k], b2[k])


def test_batches_from_step_resumes_shuffle(sintel_root):
    ds = MpiSintel(root=sintel_root)  # 4 samples
    mk = lambda: ShardedLoader(ds, batch_size=2, seed=7, num_workers=1)
    spe = mk().steps_per_epoch()
    assert spe == 2

    it = mk().batches()
    full = [next(it) for _ in range(5)]
    it2 = mk().batches_from_step(3)
    resumed = [next(it2) for _ in range(2)]
    for a, b in zip(full[3:], resumed):
        for k in a:
            np.testing.assert_array_equal(a[k], b[k])


@pytest.fixture
def things_root(tmp_path):
    rng = np.random.default_rng(3)
    scene = tmp_path / "FlyingThings3D"
    img_dir = scene / "frames_cleanpass/TRAIN/A/0000/left"
    img_dir.mkdir(parents=True)
    for d in ("into_future", "into_past"):
        (scene / "optical_flow/TRAIN/A/0000" / d / "left").mkdir(
            parents=True)
    for i in range(3):
        _write_img(img_dir / f"{i:04d}.png", rng)
        for d in ("into_future", "into_past"):
            flow = rng.normal(size=(H, W, 2)).astype(np.float32)
            path = scene / "optical_flow/TRAIN/A/0000" / d / "left" / \
                f"{i:04d}.pfm"
            # 3-channel little-endian PFM (flow in the first two channels)
            arr3 = np.concatenate(
                [flow, np.zeros((H, W, 1), np.float32)], axis=-1)
            with open(path, "wb") as f:
                f.write(b"PF\n")
                f.write(f"{W} {H}\n".encode())
                f.write(b"-1.0\n")
                f.write(arr3[::-1].astype("<f4").tobytes())
    return str(scene)


def test_flyingthings_directions(things_root):
    ds = FlyingThings3D(root=things_root)
    # 3 frames -> 2 future pairs + 2 past pairs (order swapped)
    assert len(ds) == 4
    s = ds.load(0)
    assert s["image1"].shape == (H, W, 3)
    assert s["flow"].shape == (H, W, 2)
    # into_past entries swap the image order relative to into_future
    futures = ds.image_list[:2]
    pasts = ds.image_list[2:]
    assert futures[0][0] == pasts[0][1]


@pytest.fixture
def hd1k_root(tmp_path):
    rng = np.random.default_rng(4)
    img_dir = tmp_path / "HD1k/hd1k_input/image_2"
    flow_dir = tmp_path / "HD1k/hd1k_flow_gt/flow_occ"
    img_dir.mkdir(parents=True)
    flow_dir.mkdir(parents=True)
    for seq in range(2):
        for i in range(3):
            _write_img(img_dir / f"{seq:06d}_{i:04d}.png", rng)
            frame_utils.write_flow_kitti(
                str(flow_dir / f"{seq:06d}_{i:04d}.png"),
                rng.normal(scale=3, size=(H, W, 2)).astype(np.float32))
    return str(tmp_path / "HD1k")


def test_hd1k_sequence_scan(hd1k_root):
    ds = HD1K(root=hd1k_root)
    # per sequence: len(flows)-1 = 2 pairs, 2 sequences -> 4
    assert len(ds) == 4
    s = ds.load(0)
    assert s["flow"].shape == (H, W, 2)
    assert s["valid"].shape == (H, W)
