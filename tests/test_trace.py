"""Distributed-tracing tests (tier-1): span-tree round-trip through an
:class:`EventSink`-shaped sink, deterministic head sampling, tail-based
keep (error status / device retries / late non-finite verdicts), wire
header round-trip, ID propagation across the engine's dispatcher and
device threads, the router's hedge+failover single-tree invariant, the
zero-overhead contract at ``sample_rate=0``, and the
``scripts/trace_report.py`` / ``scripts/trace_smoke.py`` ``--tiny``
round-trips.

Budget discipline mirrors test_fleet.py: ONE engine compiles the single
``(40, 56) x b2`` program (module-scoped ``aot_dir``); every engine and
fleet in the file imports that artifact."""

import importlib.util
import json
import os.path as osp
import random
import time

import numpy as np
import pytest

from raft_tpu import chaos
from raft_tpu.config import RAFTConfig
from raft_tpu.obs import trace
from raft_tpu.serve import (FleetConfig, FlowRouter, InferenceEngine,
                            ReplicaFleet, RouterConfig, ServeConfig)

REPO = osp.dirname(osp.dirname(osp.abspath(__file__)))

CFG = RAFTConfig.small_model()  # fp32: CPU-friendly
ITERS = 2
SHAPE = (36, 52)                # -> bucket (40, 56)


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, osp.join(REPO, "scripts", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _serve_cfg(**kw):
    base = dict(iters=ITERS, max_batch=2, batch_sizes=(2,),
                max_wait_ms=5, max_queue=64)
    base.update(kw)
    return ServeConfig(**base)


def _images(rng, h=SHAPE[0], w=SHAPE[1]):
    return (rng.uniform(0, 255, (h, w, 3)).astype(np.float32),
            rng.uniform(0, 255, (h, w, 3)).astype(np.float32))


def _wait_for(pred, timeout_s, what):
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        if pred():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {what}")


class _ListSink:
    """EventSink-shaped sink capturing records in-process."""

    def __init__(self):
        self.records = []

    def emit(self, event, **fields):
        self.records.append(dict(event=event, **fields))

    def spans(self, name=None):
        return [r for r in self.records
                if r["event"] == trace.EVENT
                and (name is None or r["name"] == name)]

    def flush(self):
        pass

    def close(self):
        pass


@pytest.fixture(autouse=True)
def _clean_process_state():
    chaos.uninstall()
    trace.reset_default_tracer()
    yield
    chaos.uninstall()
    trace.reset_default_tracer()
    trace.set_active_profile(None)


@pytest.fixture(scope="module")
def variables():
    import jax

    from raft_tpu.models.raft import RAFT

    model_img = jax.numpy.zeros((1, 40, 56, 3))
    rng = jax.random.PRNGKey(0)
    return RAFT(CFG).init({"params": rng, "dropout": rng},
                          model_img, model_img, iters=1)


@pytest.fixture(scope="module")
def aot_dir(variables, tmp_path_factory):
    """The file's ONE compile: warm a throwaway engine and export."""
    d = str(tmp_path_factory.mktemp("aot"))
    eng = InferenceEngine(variables, CFG, _serve_cfg())
    eng.start()
    try:
        eng.warmup([SHAPE])
        eng.export_aot(d)
    finally:
        eng.stop()
    return d


def _mk_engine(variables, aot_dir, **scfg_kw):
    return InferenceEngine(variables, CFG,
                           _serve_cfg(aot_dir=aot_dir, **scfg_kw))


def _mk_fleet(variables, aot_dir, *, scfg=None, **fcfg_kw):
    kw = dict(replicas=2, aot_dir=aot_dir, warmup_shapes=(SHAPE,),
              auto_export_aot=False, restart_backoff_s=0.05,
              restart_backoff_max_s=0.4, health_poll_s=0.05)
    kw.update(fcfg_kw)
    return ReplicaFleet(variables, CFG, scfg or _serve_cfg(),
                        FleetConfig(**kw))


# ---------------------------------------------------------------------------
# core API: tree round-trip, sampling, tail-keep, wire header
# ---------------------------------------------------------------------------


def test_span_tree_round_trip():
    sink = _ListSink()
    tracer = trace.Tracer(sink=sink, sample_rate=1.0)
    root = tracer.start_trace("req", bucket="40x56")
    child = root.child("queue")
    child.end()
    with trace.use_context(root):
        with trace.trace_span("pad", real=2) as pad:
            assert trace.current() is pad
    assert not sink.spans(), "nothing may emit before the root closes"
    root.end(hedged=False)
    recs = sink.spans()
    assert [r["name"] for r in recs] == ["queue", "pad", "req"]
    assert len({r["trace_id"] for r in recs}) == 1
    by_name = {r["name"]: r for r in recs}
    assert by_name["queue"]["parent_id"] == by_name["req"]["span_id"]
    assert by_name["pad"]["parent_id"] == by_name["req"]["span_id"]
    assert by_name["req"]["parent_id"] is None
    assert by_name["pad"]["real"] == 2        # attrs flatten into the
    assert by_name["req"]["hedged"] is False  # record (end() kwargs too)
    assert all(r["dur_s"] >= 0 for r in recs)


def test_sampling_deterministic_at_fixed_seed():
    def verdicts(n=32):
        sink = _ListSink()
        tracer = trace.Tracer(sink=sink, sample_rate=0.3, seed=42)
        out = []
        for i in range(n):
            before = len(sink.spans())
            tracer.start_trace("t", i=i).end()
            out.append(len(sink.spans()) > before)
        return out

    a, b = verdicts(), verdicts()
    assert a == b, "same seed must sample the same traces"
    assert True in a and False in a, "0.3 over 32 coins hits both ways"
    # and the coin IS the seeded PRNG stream — pinned, not incidental
    rnd = random.Random(42)
    assert a == [rnd.random() < 0.3 for _ in range(32)]


def test_tail_keep_error_and_late_recovery():
    sink = _ListSink()
    # seed 0's first coins all miss a 0.001 rate: heads-dropped traces
    tracer = trace.Tracer(sink=sink, sample_rate=0.001, seed=0)

    # an error status forces the trace out despite the dropped coin
    root = tracer.start_trace("req")
    root.child("device").end(status="error", error="boom")
    root.end(status="error", error="boom")
    assert [r["name"] for r in sink.spans()] == ["device", "req"]

    # a clean dropped trace parks in the ring ...
    sink.records.clear()
    tracer.start_trace("train_step", step=7).end()
    tracer.start_trace("train_step", step=8).end()
    assert not sink.spans()
    # ... until a late verdict (non-finite at step 8) recovers it
    assert tracer.emit_recent_dropped(steps=[8]) == 1
    recs = sink.spans("train_step")
    assert len(recs) == 1 and recs[0]["step"] == 8


def test_wire_header_round_trip():
    tracer = trace.Tracer(sink=_ListSink(), sample_rate=1.0)
    span = tracer.start_trace("route")
    hdr = trace.format_header(span)
    tid, parent, sampled = trace.parse_header(hdr)
    assert (tid, parent, sampled) == (span.trace_id, span.span_id, True)
    for bad in (None, "", "x", "a-b", "a-b-c-d", "zz-yy-s",
                f"{span.trace_id}-{span.span_id}-q"):
        assert trace.parse_header(bad) is None
    assert trace.format_header(None) is None
    assert trace.format_header(trace.NOOP_SPAN) is None
    # continuation: a downstream tracer with tracing OFF still records
    # because the upstream sampling decision rides the header
    sink2 = _ListSink()
    downstream = trace.Tracer(sink=sink2, sample_rate=0.0)
    cont = downstream.start_trace("serve_http", trace_id=tid,
                                  parent_id=parent, sampled=sampled)
    cont.end()
    recs = sink2.spans()
    assert len(recs) == 1
    assert recs[0]["trace_id"] == span.trace_id
    assert recs[0]["parent_id"] == span.span_id


def test_noop_singleton_when_disabled():
    tracer = trace.Tracer(sample_rate=0.0)
    assert not tracer.enabled
    assert tracer.start_trace("x") is trace.NOOP_SPAN
    assert tracer.begin("x") is trace.NOOP_SPAN
    assert trace.trace_span("x") is trace.NOOP_SPAN  # no context
    assert not trace.NOOP_SPAN  # falsy: `if span` guards all skip
    # the no-op absorbs the whole Span surface without allocating
    trace.NOOP_SPAN.child("y").annotate(z=1)
    trace.NOOP_SPAN.mark_keep()
    trace.NOOP_SPAN.end(status="error")
    with trace.use_context(trace.NOOP_SPAN):
        assert trace.current() is None


# ---------------------------------------------------------------------------
# engine: dispatcher -> device-thread propagation; tail-keep on chaos
# ---------------------------------------------------------------------------


def test_engine_propagates_ids_across_threads(variables, aot_dir):
    """The submitting thread's context rides the request through the
    dispatcher to the device worker: queue/pad/device land in the SAME
    trace, parented to the submitting span."""
    sink = _ListSink()
    tracer = trace.Tracer(sink=sink, sample_rate=1.0)
    eng = _mk_engine(variables, aot_dir).start()
    try:
        rng = np.random.default_rng(1)
        root = tracer.start_trace("req")
        with trace.use_context(root):
            fut = eng.submit(*_images(rng))
        flow = fut.result(timeout=60)
        assert flow.shape == SHAPE + (2,)
        root.end()
        _wait_for(lambda: len(sink.spans("device")) == 1, 10,
                  "the device worker's spans")
        by_name = {r["name"]: r for r in sink.spans()}
        assert {"queue", "pad", "device"} <= set(by_name)
        assert {r["trace_id"] for r in sink.spans()} \
            == {root.trace_id}
        for name in ("queue", "pad", "device"):
            assert by_name[name]["parent_id"] == root.span_id, name
        assert by_name["device"]["retries"] == 0
    finally:
        eng.stop()


def test_device_err_tail_keeps_trace(variables, aot_dir):
    """An injected transient ``device_err`` makes the engine retry; the
    retried batch tail-keeps the trace even though the head-sampling
    coin DROPPED it."""
    sink = _ListSink()
    tracer = trace.Tracer(sink=sink, sample_rate=0.001, seed=0)
    eng = _mk_engine(variables, aot_dir).start()
    try:
        chaos.install(chaos.FaultPlan.parse("device_err@batch=1",
                                            seed=0))
        rng = np.random.default_rng(2)
        root = tracer.start_trace("req")
        assert not root.sampled, "rate=0.001/seed=0 must drop the coin"
        with trace.use_context(root):
            fut = eng.submit(*_images(rng))
        flow = fut.result(timeout=60)
        assert flow.shape == SHAPE + (2,)
        root.end()
        _wait_for(lambda: len(sink.spans("device")) == 1, 10,
                  "the tail-kept device span")
        dev = sink.spans("device")[0]
        assert dev["retries"] >= 1, dev
        assert sink.spans("req"), "tail-keep must flush the whole tree"
    finally:
        eng.stop()


def test_zero_overhead_when_disabled(variables, aot_dir):
    """``sample_rate=0`` serves with NO span machinery: requests carry
    ``trace=None``, the default tracer hands out the no-op singleton,
    and not one trace_span event reaches the sink."""
    sink = _ListSink()
    trace.configure(sample_rate=0.0, sink=sink)
    assert trace.default_tracer().begin("route") is trace.NOOP_SPAN
    eng = _mk_engine(variables, aot_dir).start()
    try:
        rng = np.random.default_rng(3)
        fut = eng.submit(*_images(rng))
        assert fut.result(timeout=60).shape == SHAPE + (2,)
        assert not sink.spans()
        assert trace.current() is None
    finally:
        eng.stop()


# ---------------------------------------------------------------------------
# router: hedge + failover keep ONE tree per request
# ---------------------------------------------------------------------------


def test_router_failover_one_tree(variables, aot_dir):
    """``replica_kill`` fails the first attempt; the router fails over.
    The trace reconstructs as ONE tree: a ``route`` root with TWO
    attempt subtrees — the error loser and the winner — and the error
    status tail-keeps it past the dropped sampling coin."""
    sink = _ListSink()
    trace.configure(sample_rate=0.001, seed=0, sink=sink)
    fleet = _mk_fleet(variables, aot_dir)
    fleet.start()
    try:
        router = FlowRouter(fleet, RouterConfig())
        chaos.install(chaos.FaultPlan.parse("replica_kill@batch=1",
                                            seed=0))
        rng = np.random.default_rng(4)
        flow = router.infer(*_images(rng), timeout=60)
        assert flow.shape == SHAPE + (2,)
        assert router.router_stats()["failovers_total"] >= 1
        _wait_for(lambda: len(sink.spans("attempt")) >= 2, 10,
                  "both attempt spans")
        roots = [r for r in sink.spans("route")
                 if r["parent_id"] is None]
        assert len(roots) == 1, roots
        tid = roots[0]["trace_id"]
        attempts = sink.spans("attempt")
        assert all(a["trace_id"] == tid for a in attempts)
        assert all(a["parent_id"] == roots[0]["span_id"]
                   for a in attempts)
        statuses = sorted(a["status"] for a in attempts)
        assert statuses == ["error", "ok"], attempts
        assert {a["replica"] for a in attempts} == {"r0", "r1"}
        assert roots[0]["replicas_tried"] == 2
    finally:
        fleet.stop(drain=False)


def test_router_hedge_one_tree(variables, aot_dir):
    """``replica_slow`` fires the bounded hedge: two attempts on two
    replicas, first result wins — still ONE tree, with the winner
    marked ``won=True``/``hedge=True`` and the straggler's spans
    stitched in late (it ends after the root flushed)."""
    sink = _ListSink()
    trace.configure(sample_rate=1.0, sink=sink)
    fleet = _mk_fleet(variables, aot_dir,
                      scfg=_serve_cfg(aot_dir=aot_dir, chaos_slow_s=3.0))
    fleet.start()
    try:
        router = FlowRouter(fleet, RouterConfig(hedge_timeout_s=0.25))
        chaos.install(chaos.FaultPlan.parse("replica_slow@batch=1",
                                            seed=0))
        rng = np.random.default_rng(5)
        t0 = time.perf_counter()
        flow = router.infer(*_images(rng), timeout=60)
        dt = time.perf_counter() - t0
        assert flow.shape == SHAPE + (2,)
        assert dt < 2.5, f"hedge did not cover the {dt:.1f}s straggler"
        _wait_for(lambda: len(sink.spans("attempt")) >= 2, 30,
                  "the straggler's late attempt span")
        roots = [r for r in sink.spans("route")
                 if r["parent_id"] is None]
        assert len(roots) == 1 and roots[0]["hedged"] is True
        attempts = sink.spans("attempt")
        assert len(attempts) == 2
        assert {a["trace_id"] for a in attempts} \
            == {roots[0]["trace_id"]}
        winner = next(a for a in attempts if a["won"])
        loser = next(a for a in attempts if not a["won"])
        assert winner["hedge"] is True and loser["hedge"] is False
        assert loser["dur_s"] > winner["dur_s"]
        # each attempt subtree carries its replica's device span
        devices = sink.spans("device")
        assert {d["parent_id"] for d in devices} \
            == {a["span_id"] for a in attempts}
    finally:
        fleet.stop(drain=False)


# ---------------------------------------------------------------------------
# tooling round-trips (tier-1 wiring of the analysis surface)
# ---------------------------------------------------------------------------


def test_trace_report_tiny(capsys):
    mod = _load_script("trace_report")
    assert mod.main(["--tiny"]) == 0
    out = capsys.readouterr().out.strip().splitlines()
    rec = json.loads(out[-1])
    assert rec["metric"] == "trace_report"
    assert rec["config"]["traces_total"] == 2
    assert {"queue", "pad", "device"} <= set(
        rec["config"]["serve_span_names"])
    assert rec["config"]["critical_path_ms"]["device"] > 0


def test_trace_smoke_tiny(capsys):
    """The end-to-end drill: 2-replica fleet under ``replica_slow``,
    hedged request -> one reconstructed tree, critical path through the
    winner, Perfetto + bench-record exports (the tier-1 acceptance
    wiring for docs/OBSERVABILITY.md's tracing section)."""
    mod = _load_script("trace_smoke")
    rc = mod.main(["--tiny"])
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0, rec
    assert rec["metric"] == "trace_smoke" and rec["value"] == 1.0
    cfg = rec["config"]
    assert cfg["one_tree"]["spans"] == 9  # route + 2x(attempt+q/p/d)
    assert cfg["critical_path"][-1].startswith("device:")
    assert cfg["exports"]["traces_total"] == 3
