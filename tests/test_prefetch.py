"""Overlapped input pipeline (`raft_tpu/data/prefetch.py`) + gradient
accumulation (`train/step.py accum_steps`) tests.

Fast tier: synthetic in-memory datasets, stubbed or tiny jitted steps.
The contracts pinned here are the PR-3 acceptance criteria: prefetch
on/off batch streams bit-identical (including mid-epoch resume and the
resume-keyed noise RNG), buffer boundedness, steady-state queue wait
< 10% of step time under overlap, accum grads == full-batch grads, and
the bench_input --tiny smoke.
"""

import gc
import json
import os.path as osp
import time
import weakref

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu.data.datasets import FlowDataset, ShardedLoader
from raft_tpu.data.prefetch import DevicePipeline

REPO = osp.dirname(osp.dirname(osp.abspath(__file__)))


class _SynthDataset(FlowDataset):
    """In-memory dataset: deterministic f(index) content plus an
    rng-dependent 'augmentation' draw, so stream-identity checks cover
    the per-sample RNG plumbing too."""

    def __init__(self, n=13, hw=(8, 10)):
        super().__init__()
        self.hw = hw
        self.image_list = [("a", "b")] * n  # drives len()
        self.loads = []  # (epoch-agnostic) load-call ledger

    def load(self, index, rng=None):
        self.loads.append(index)
        H, W = self.hw
        base = np.full((H, W, 3), float(index), np.float32)
        jitter = (rng.standard_normal((H, W, 3)).astype(np.float32)
                  if rng is not None else 0.0)
        return {"image1": base + jitter, "image2": base * 2.0,
                "flow": np.zeros((H, W, 2), np.float32),
                "valid": np.ones((H, W), np.float32)}


def _noise_fn(seed, start_step):
    """The loop's producer-side prep: resume-keyed noise RNG
    (train/loop.py builds exactly this)."""
    from raft_tpu.train.loop import add_image_noise

    rng = np.random.default_rng(
        np.random.SeedSequence([seed + 1, start_step]))
    return lambda b: add_image_noise(rng, b)


def _take(pipe, n):
    try:
        return [next(pipe) for _ in range(n)]
    finally:
        pipe.close()


# ---------------------------------------------------------------------
# stream identity: prefetch on/off, resume, noise
# ---------------------------------------------------------------------

def test_prefetch_on_off_identical_streams_and_resume():
    """Acceptance: prefetch-on and prefetch-off batch streams are
    bit-identical, including mid-epoch resume via batches_from_step and
    the stateful resume-keyed noise RNG applied in the producer."""
    ds = _SynthDataset(n=13)  # batch 2, drop_last -> 6 steps/epoch

    def stream(depth, start_step):
        loader = ShardedLoader(ds, batch_size=2, seed=7, num_workers=2)
        pipe = DevicePipeline(loader.batches_from_step(start_step),
                              prep_fn=_noise_fn(7, start_step),
                              depth=depth)
        return _take(pipe, 8)  # crosses the epoch boundary

    for start in (0, 5):  # fresh run + mid-epoch resume
        serial = stream(0, start)
        overlapped = stream(3, start)
        assert len(serial) == len(overlapped) == 8
        for a, b in zip(serial, overlapped):
            for k in a:
                np.testing.assert_array_equal(a[k], b[k])


def test_prefetch_device_put_parity_and_sharding():
    """With the real sharder, the overlapped arm yields committed
    jax.Arrays with values identical to the serial arm's."""
    from raft_tpu.parallel import make_batch_sharder, make_mesh

    put = make_batch_sharder(make_mesh())
    ds = _SynthDataset(n=20)

    def stream(depth):
        loader = ShardedLoader(ds, batch_size=8, seed=3, num_workers=2)
        return _take(DevicePipeline(loader.batches(), put_fn=put,
                                    depth=depth), 3)

    serial, overlapped = stream(0), stream(3)
    for a, b in zip(serial, overlapped):
        for k in a:
            assert isinstance(b[k], jax.Array)
            np.testing.assert_array_equal(np.asarray(a[k]),
                                          np.asarray(b[k]))


def test_loader_prefetch_batches_stream_invariant_and_window():
    """The decode-window knob changes HOW FAR the pool runs ahead, never
    the stream; the window actually bounds load-call runahead."""
    def batches(pb, ds):
        loader = ShardedLoader(ds, batch_size=2, seed=5, num_workers=2,
                               prefetch_batches=pb)
        it = loader.batches()
        return [next(it) for _ in range(7)]

    a = batches(0, _SynthDataset(n=13))
    b = batches(5, _SynthDataset(n=13))
    for x, y in zip(a, b):
        for k in x:
            np.testing.assert_array_equal(x[k], y[k])

    # window = prefetch_batches * batch_size = 2 samples: after pulling
    # 2 batches (4 samples), at most 4 + 2 loads may have been submitted.
    ds = _SynthDataset(n=13)
    loader = ShardedLoader(ds, batch_size=2, seed=5, num_workers=2,
                           prefetch_batches=1)
    it = loader.batches()
    next(it), next(it)
    time.sleep(0.2)  # give the pool every chance to overrun
    assert len(ds.loads) <= 2 * 2 + 1 * 2, ds.loads
    it.close()


# ---------------------------------------------------------------------
# boundedness + lifecycle
# ---------------------------------------------------------------------

def test_prefetch_buffer_bounded():
    """The producer never pulls more than `depth` batches beyond what
    the consumer has taken (slot acquired BEFORE the source is pulled)."""
    pulled = [0]

    def src():
        while True:
            pulled[0] += 1
            yield {"x": np.zeros((4,), np.float32)}

    depth = 3
    pipe = DevicePipeline(src(), depth=depth)
    time.sleep(0.3)  # producer free-runs against an instant source
    assert pulled[0] <= depth
    for i in range(5):
        next(pipe)
        time.sleep(0.05)
        assert pulled[0] <= i + 1 + depth
    pipe.close()
    assert not pipe._thread.is_alive()


def test_prefetch_close_frees_buffered_batches():
    """Weakref/alloc check: close() drops every buffered batch — a
    leaked queue would pin device memory across runs."""
    refs = []

    def src():
        while True:
            a = np.zeros((64,), np.float32)
            refs.append(weakref.ref(a))
            yield {"x": a}

    pipe = DevicePipeline(src(), depth=4)
    first = next(pipe)
    time.sleep(0.2)  # let the buffer fill
    thread = pipe._thread
    pipe.close()
    assert len(refs) >= 3  # the buffer did fill before close
    del first, pipe  # the source generator's frame holds the last yield
    gc.collect()
    assert sum(r() is not None for r in refs) == 0
    assert not thread.is_alive()


def test_prefetch_producer_error_propagates():
    def src():
        yield {"x": np.zeros(2, np.float32)}
        raise RuntimeError("decode failed")

    for depth in (0, 2):
        pipe = DevicePipeline(src(), depth=depth)
        next(pipe)
        with pytest.raises(RuntimeError, match="decode failed"):
            for _ in range(3):
                next(pipe)
        if depth:  # after the error the pipeline is closed
            with pytest.raises(StopIteration):
                next(pipe)
        pipe.close()

    with pytest.raises(ValueError, match="depth"):
        DevicePipeline(iter(()), depth=-1)


def test_prefetch_producer_crash_preserves_type_and_close_joins():
    """Producer-crash semantics (the prefetch.py error-relay path): a
    producer that raises mid-stream re-raises the ORIGINAL exception
    object in the consumer's next(), and close() afterwards returns
    promptly with the thread joined — no hang, no leaked thread,
    idempotent."""

    class BoomError(Exception):
        pass

    boom = BoomError("mid-stream decode crash")

    def src():
        yield {"x": np.zeros((4,), np.float32)}
        yield {"x": np.ones((4,), np.float32)}
        raise boom

    pipe = DevicePipeline(src(), depth=2)
    next(pipe)
    next(pipe)
    with pytest.raises(BoomError) as ei:
        next(pipe)
    assert ei.value is boom  # the original object, not a re-wrap
    # after the error the pipeline is closed and stays closed
    with pytest.raises(StopIteration):
        next(pipe)
    t0 = time.perf_counter()
    pipe.close()
    pipe.close()  # idempotent
    assert time.perf_counter() - t0 < 5.0
    assert not pipe._thread.is_alive()


# ---------------------------------------------------------------------
# the overlap acceptance criterion
# ---------------------------------------------------------------------

def test_queue_wait_under_overlap_acceptance():
    """Synthetic slow-step + fast-loader: steady-state consumer queue
    wait is < 10% of step time with device prefetch on, vs ~ the serial
    fetch cost with it off (the PR-3 acceptance criterion)."""
    step_s, fetch_s, n = 0.05, 0.015, 10

    def src():
        while True:
            time.sleep(fetch_s)
            yield {"x": np.zeros((8,), np.float32)}

    def waits(depth):
        pipe = DevicePipeline(src(), depth=depth)
        ws = []
        try:
            for _ in range(n):
                t = time.perf_counter()
                next(pipe)
                ws.append(time.perf_counter() - t)
                time.sleep(step_s)  # the synthetic "device step"
        finally:
            pipe.close()
        return ws[2:]  # steady state: past the pipeline fill

    overlapped = waits(2)
    serial = waits(0)
    assert float(np.median(overlapped)) < 0.1 * step_s, overlapped
    assert float(np.median(serial)) >= 0.5 * fetch_s, serial


def test_loop_noise_identical_prefetch_on_off(tmp_path, monkeypatch):
    """End-to-end through train(): the batches the step consumes —
    including add_noise applied in the pipeline producer — are
    bit-identical at device_prefetch 0 vs 3 (determinism satellite)."""
    from raft_tpu.config import RAFTConfig, TrainConfig
    from raft_tpu.train import loop as loop_mod
    from raft_tpu.train.state import TrainState

    mcfg = RAFTConfig.small_model(corr_levels=2, corr_radius=2)

    def batches(n=8, bs=8, hw=(8, 10)):
        rng = np.random.default_rng(0)
        H, W = hw
        for _ in range(n):
            yield {"image1": rng.uniform(0, 255, (bs, H, W, 3)
                                         ).astype(np.float32),
                   "image2": rng.uniform(0, 255, (bs, H, W, 3)
                                         ).astype(np.float32),
                   "flow": np.zeros((bs, H, W, 2), np.float32),
                   "valid": np.ones((bs, H, W), np.float32)}

    def run(depth, name):
        captured = []

        def fake_init_state(model, tx, rng, size):
            params = {"w": np.zeros((2, 2), np.float32)}
            return TrainState(step=jnp.asarray(0, jnp.int32),
                              params=params, batch_stats={},
                              opt_state=tx.init(params))

        def fake_make_train_step(model, tx, cfg, mesh,
                                 shard_spatial=False):
            def step_fn(state, batch, key):
                captured.append(np.asarray(batch["image1"]))
                return (state.replace(step=state.step + 1),
                        {"loss": jnp.zeros(())})
            return step_fn

        monkeypatch.setattr(loop_mod, "init_state", fake_init_state)
        monkeypatch.setattr(loop_mod, "make_train_step",
                            fake_make_train_step)
        cfg = TrainConfig(name=name, num_steps=5, batch_size=8,
                          image_size=(8, 10), iters=2, val_freq=100,
                          log_freq=100, add_noise=True, seed=11,
                          ckpt_dir=str(tmp_path / name),
                          device_prefetch=depth)
        loop_mod.train(mcfg, cfg, batches())
        return captured

    serial = run(0, "off")
    overlapped = run(3, "on")
    assert len(serial) == len(overlapped) == 5
    for a, b in zip(serial, overlapped):
        np.testing.assert_array_equal(a, b)


# ---------------------------------------------------------------------
# gradient accumulation
# ---------------------------------------------------------------------

def _make_batch(bs, hw, seed=0):
    H, W = hw
    rng = np.random.default_rng(seed)
    return {
        "image1": rng.uniform(0, 255, (bs, H, W, 3)).astype(np.float32),
        "image2": rng.uniform(0, 255, (bs, H, W, 3)).astype(np.float32),
        "flow": (4 * rng.standard_normal((bs, H, W, 2))
                 ).astype(np.float32),
        "valid": np.ones((bs, H, W), np.float32),
    }


def _tiny_step(accum, batch_size, hw=(16, 24), tx=None):
    import optax

    from raft_tpu.config import RAFTConfig, TrainConfig
    from raft_tpu.models.raft import RAFT
    from raft_tpu.train.step import init_state, make_train_step

    mcfg = RAFTConfig.small_model(corr_levels=2, corr_radius=2,
                                  scan_unroll=1)
    tcfg = TrainConfig(lr=1e-4, num_steps=10, batch_size=batch_size,
                       image_size=hw, iters=2, accum_steps=accum,
                       freeze_bn=True)
    model = RAFT(mcfg)
    # SGD(1.0) makes the update EQUAL the (negated) gradient, so the
    # param comparison below is a direct fp32 gradient comparison —
    # adam's sign-like first step would amplify noise on near-zero
    # gradient entries into full +/-lr flips.
    tx = tx or optax.sgd(1.0)
    state = init_state(model, tx, jax.random.PRNGKey(0), hw)
    return state, make_train_step(model, tx, tcfg, mesh=None,
                                  donate=False)


def test_accum_steps_matches_full_batch():
    """accum_steps=4 == accum_steps=1 at equal effective batch, within
    fp32 reduction-order tolerance (the acceptance criterion)."""
    batch = _make_batch(4, (16, 24))
    key = jax.random.PRNGKey(1)
    s1, f1 = _tiny_step(1, 4)
    s4, f4 = _tiny_step(4, 4)
    ns1, m1 = f1(s1, batch, key)
    ns4, m4 = f4(s4, batch, key)
    np.testing.assert_allclose(float(m1["loss"]), float(m4["loss"]),
                               rtol=1e-5)
    np.testing.assert_allclose(float(m1["grad_norm"]),
                               float(m4["grad_norm"]), rtol=1e-4)
    flat1 = jax.tree_util.tree_leaves(ns1.params)
    flat4 = jax.tree_util.tree_leaves(ns4.params)
    for a, b in zip(flat1, flat4):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_accum_steps_non_divisible_raises():
    s, f = _tiny_step(3, 4)
    with pytest.raises(ValueError, match="accum_steps=3 must divide"):
        f(s, _make_batch(4, (16, 24)), jax.random.PRNGKey(0))


def test_accum_peak_memory_scales_down():
    """The point of microbatching: peak live batch memory of the
    compiled step scales down with accum_steps (asserted via the
    existing hbm_usage / XLA memory-analysis path on CPU)."""
    from raft_tpu.utils.profiling import hbm_usage

    bs, hw = 8, (64, 96)
    batch = _make_batch(bs, hw)
    key = jax.random.PRNGKey(0)
    s1, f1 = _tiny_step(1, bs, hw=hw)
    s4, f4 = _tiny_step(4, bs, hw=hw)
    h1 = hbm_usage(f1, s1, batch, key)
    h4 = hbm_usage(f4, s4, batch, key)
    if "peak_hbm_gb" not in h1 or "peak_hbm_gb" not in h4:
        pytest.skip(f"XLA memory analysis unavailable: {h1} / {h4}")
    assert h4["peak_hbm_gb"] < h1["peak_hbm_gb"], (h1, h4)


# ---------------------------------------------------------------------
# bench + CLI wiring
# ---------------------------------------------------------------------

def test_bench_input_tiny_smoke(capsys):
    """scripts/bench_input.py --tiny: the tier-1 CPU smoke — runs both
    arms and prints one bench.py-format JSON line on the registered
    input-pipeline metric series."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench_input", osp.join(REPO, "scripts", "bench_input.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    mod.main(["--tiny"])
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])

    from bench import _input_metric_name

    assert rec["metric"] == _input_metric_name(32, 48)
    assert rec["unit"] == "image-pairs/sec" and rec["value"] > 0
    assert rec["config"]["overlapped"]["pairs_per_sec"] > 0
    assert rec["config"]["serial"]["pairs_per_sec"] > 0
    assert rec["config"]["overlap_speedup"] > 0


def test_cli_train_pipeline_flags_parse():
    from raft_tpu.cli.train import parse_args

    a = parse_args(["--accum-steps", "2", "--prefetch-batches", "4",
                    "--device-prefetch", "3"])
    assert (a.accum_steps, a.prefetch_batches, a.device_prefetch) \
        == (2, 4, 3)
    # underscore spellings stay accepted (repo CLI convention)
    b = parse_args(["--accum_steps", "2", "--prefetch_batches", "4",
                    "--device_prefetch", "0"])
    assert (b.accum_steps, b.prefetch_batches, b.device_prefetch) \
        == (2, 4, 0)


def test_interrupt_predicate_unblocks_waiting_consumer():
    """Satellite (PR 7): a preemption flag set while the consumer is
    blocked in ``next()`` on an EMPTY buffer is observed within the
    poll interval — ``PipelineInterrupted`` — instead of going unseen
    until a batch arrives (the old SIGTERM-during-input-stall caveat).
    The pipeline stays usable afterwards: not a stream error."""
    import threading

    from raft_tpu.data.prefetch import PipelineInterrupted

    flag = threading.Event()
    release = threading.Event()

    def src():
        yield {"x": np.zeros((2,), np.float32)}
        release.wait(30.0)  # stall the producer: buffer stays empty
        yield {"x": np.ones((2,), np.float32)}

    pipe = DevicePipeline(src(), depth=2, interrupt=flag.is_set,
                          interrupt_poll_s=0.02)
    assert next(pipe)["x"][0] == 0.0
    timer = threading.Timer(0.05, flag.set)
    timer.start()
    t0 = time.perf_counter()
    with pytest.raises(PipelineInterrupted):
        next(pipe)
    # observed within ~poll interval of the flag flip, nowhere near the
    # 30 s the blocked source would have held the old blocking get
    assert time.perf_counter() - t0 < 5.0
    timer.cancel()

    flag.clear()
    release.set()  # input resumes -> the same pipeline delivers
    assert next(pipe)["x"][0] == 1.0
    pipe.close()


def test_interrupt_predicate_ignored_while_batches_buffered():
    """The poll is backpressure-free: with batches in the buffer the
    flag is never even consulted — delivery wins (the train loop's
    preempt seam handles the flag between steps)."""
    def src():
        for i in range(3):
            yield {"x": np.full((2,), float(i), np.float32)}

    pipe = DevicePipeline(src(), depth=2, interrupt=lambda: True,
                          interrupt_poll_s=0.02)
    deadline = time.time() + 10.0
    while pipe.buffered() < 1 and time.time() < deadline:
        time.sleep(0.005)
    assert pipe.buffered() >= 1
    assert next(pipe)["x"][0] == 0.0  # delivered despite the true flag
    pipe.close()
