"""Parity tests for raft_tpu.ops.sampler against the PyTorch reference
semantics (grid_sample align_corners=True, zeros padding)."""

import numpy as np
import pytest

import jax.numpy as jnp

from raft_tpu.ops import (
    bilinear_sampler,
    coords_grid,
    resize_bilinear_align_corners,
    upflow8,
)
from tests.reference_oracle import skip_without_reference


def test_coords_grid_values():
    g = np.asarray(coords_grid(2, 3, 4))
    assert g.shape == (2, 3, 4, 2)
    # last axis is (x, y)
    assert np.array_equal(g[0, :, :, 0], np.tile(np.arange(4), (3, 1)))
    assert np.array_equal(g[0, :, :, 1], np.tile(np.arange(3)[:, None], (1, 4)))
    assert np.array_equal(g[0], g[1])


def test_bilinear_sampler_exact_integer_coords():
    rng = np.random.default_rng(0)
    img = rng.normal(size=(1, 5, 7, 3)).astype(np.float32)
    # integer coords must return exact pixels
    coords = np.stack(np.meshgrid(np.arange(7), np.arange(5)), axis=-1)
    coords = coords[None].astype(np.float32)  # (1, 5, 7, 2) (x, y)
    out = np.asarray(bilinear_sampler(jnp.asarray(img), jnp.asarray(coords)))
    np.testing.assert_allclose(out, img, rtol=1e-6)


def test_bilinear_sampler_vs_torch_grid_sample():
    skip_without_reference()
    import torch
    import torch.nn.functional as F

    rng = np.random.default_rng(1)
    img = rng.normal(size=(2, 9, 13, 4)).astype(np.float32)
    # coords include out-of-bounds on purpose
    coords = rng.uniform(-3, 16, size=(2, 6, 5, 2)).astype(np.float32)

    out = np.asarray(bilinear_sampler(jnp.asarray(img), jnp.asarray(coords)))

    timg = torch.from_numpy(img).permute(0, 3, 1, 2)  # NCHW
    H, W = 9, 13
    x = torch.from_numpy(coords[..., 0]) * 2 / (W - 1) - 1
    y = torch.from_numpy(coords[..., 1]) * 2 / (H - 1) - 1
    grid = torch.stack([x, y], dim=-1)
    ref = F.grid_sample(timg, grid, align_corners=True, padding_mode="zeros")
    ref = ref.permute(0, 2, 3, 1).numpy()
    np.testing.assert_allclose(out, ref, atol=1e-5)


def test_bilinear_sampler_mask():
    img = jnp.ones((1, 4, 4, 1))
    coords = jnp.array([[[[0.0, 0.0], [1.5, 1.5], [3.5, 2.0], [-1.0, 1.0]]]])
    _, mask = bilinear_sampler(img, coords, mask=True)
    # strict bounds: 0 is NOT in-bounds (matches reference utils.py:67-69)
    np.testing.assert_array_equal(np.asarray(mask)[0, 0], [0.0, 1.0, 0.0, 0.0])


def test_resize_align_corners_vs_torch():
    skip_without_reference()
    import torch
    import torch.nn.functional as F

    rng = np.random.default_rng(2)
    x = rng.normal(size=(2, 6, 7, 3)).astype(np.float32)
    out = np.asarray(resize_bilinear_align_corners(jnp.asarray(x), (48, 56)))
    t = torch.from_numpy(x).permute(0, 3, 1, 2)
    ref = F.interpolate(t, size=(48, 56), mode="bilinear", align_corners=True)
    ref = ref.permute(0, 2, 3, 1).numpy()
    np.testing.assert_allclose(out, ref, atol=1e-5)


def test_upflow8_scales_and_interpolates():
    flow = jnp.ones((1, 4, 5, 2)) * 2.0
    up = np.asarray(upflow8(flow))
    assert up.shape == (1, 32, 40, 2)
    np.testing.assert_allclose(up, 16.0, rtol=1e-6)
