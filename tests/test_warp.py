"""forward_interpolate vs scipy.griddata oracle (SURVEY C7, utils.py:26-54)."""

import numpy as np
from scipy import interpolate

from raft_tpu.utils.warp import forward_interpolate


def _griddata_oracle(flow):
    # Transcription of the reference implementation (utils.py:26-54),
    # channel-last layout.
    dx, dy = flow[..., 0], flow[..., 1]
    ht, wd = dx.shape
    x0, y0 = np.meshgrid(np.arange(wd), np.arange(ht))
    x1 = (x0 + dx).reshape(-1)
    y1 = (y0 + dy).reshape(-1)
    dxf, dyf = dx.reshape(-1), dy.reshape(-1)
    valid = (x1 > 0) & (x1 < wd) & (y1 > 0) & (y1 < ht)
    x1, y1, dxf, dyf = x1[valid], y1[valid], dxf[valid], dyf[valid]
    fx = interpolate.griddata((x1, y1), dxf, (x0, y0),
                              method="nearest", fill_value=0)
    fy = interpolate.griddata((x1, y1), dyf, (x0, y0),
                              method="nearest", fill_value=0)
    return np.stack([fx, fy], axis=-1).astype(np.float32)


def test_matches_griddata():
    rng = np.random.RandomState(0)
    flow = rng.randn(14, 19, 2).astype(np.float32) * 3
    ours = forward_interpolate(flow)
    oracle = _griddata_oracle(flow)
    # Nearest-neighbor ties can break differently; require near-total
    # agreement and tiny max deviation on the rest.
    agree = np.isclose(ours, oracle).mean()
    assert agree > 0.99, agree


def test_constant_flow_is_preserved():
    flow = np.ones((12, 12, 2), np.float32) * 2.0
    out = forward_interpolate(flow)
    np.testing.assert_allclose(out, flow)


def test_all_out_of_bounds():
    flow = np.full((6, 6, 2), 100.0, np.float32)
    out = forward_interpolate(flow)
    np.testing.assert_array_equal(out, np.zeros_like(flow))
