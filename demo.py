#!/usr/bin/env python
"""Root-level demo entry point (reference ``python demo.py``,
demo.py:66-75).  All logic lives in :mod:`raft_tpu.cli.demo`."""
from raft_tpu.cli.demo import main

if __name__ == "__main__":
    main()
